"""Serialization + compression throughput benchmark.

Reference equivalent: ``/root/reference/benchmarks/serialization_benchmark.cpp``
and ``compression_benchmark.cpp`` — how fast can an activation/parameter
payload be framed, compressed, and recovered. Every codec row is gated on an
exact round-trip (compress→decompress→bitwise compare), and the checkpoint
rows gate on a full save→load→tree-equality cycle.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile
import time

import numpy as np

from common import Result, print_table, report, tiny_mode


def _time_host(fn, reps: int = 5):
    best = float("inf")
    out = None
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return best, out


def run() -> dict:
    from dcnn_tpu.utils.compression import (MetaCompressor, RawCompressor,
                                            ZlibCompressor)

    results = []
    mc = MetaCompressor()
    mb = 4 if tiny_mode() else 64
    rng = np.random.default_rng(0)
    # two payload classes the pipeline actually ships: near-incompressible
    # activations, and structured (quantized-ish) gradients with many repeats
    payloads = {
        "activations": rng.standard_normal(mb * 1024 * 256).astype(np.float32),
        "sparse_grads": (rng.standard_normal(mb * 1024 * 256) *
                         (rng.random(mb * 1024 * 256) < 0.05)).astype(np.float32),
        # trained-weight-shaped payload: small-magnitude values cast to bf16
        # (viewed as u16 so the npz framing stays vanilla numpy) — the
        # dominant checkpoint/pipeline wire-dtype class
        "weights_bf16": (
            (rng.standard_normal(mb * 1024 * 512) * 0.05).astype(np.float32)
            .view(np.uint32) >> np.uint32(16)).astype(np.uint16),
    }
    codecs = {"raw": RawCompressor(), "zlib1": ZlibCompressor(level=1)}
    if 2 in mc.codecs:
        codecs["zstd"] = mc.codecs[2]
    try:
        from dcnn_tpu.utils.compression import (Lz4Compressor,
                                                ShuffleZstdCompressor)
        codecs["lz4"] = Lz4Compressor()
        # level 9: the reference Lz4hc default
        # (internal_compressor.hpp:10-15); same codec id / block format
        codecs["lz4hc9"] = Lz4Compressor(level=9)
        codecs["shuffle_zstd"] = ShuffleZstdCompressor()
    except RuntimeError:
        pass  # no native toolchain — numpy-only host

    for pname, arr in payloads.items():
        nbytes = arr.nbytes
        for cname, codec in codecs.items():
            dt_c, blob = _time_host(
                lambda a=arr, c=codec: mc.compress_array(a, c))
            dt_d, back = _time_host(lambda b=blob: mc.decompress_array(b))
            ok = (back.dtype == arr.dtype and back.shape == arr.shape
                  and np.array_equal(back, arr))
            results.append(Result(
                f"compress_{pname}_{cname}", dt_c, nbytes / dt_c / 1e9, "GB/s",
                ok, 0.0 if ok else float("inf"),
                extra={"ratio": round(nbytes / len(blob), 3)}))
            results.append(Result(
                f"decompress_{pname}_{cname}", dt_d, nbytes / dt_d / 1e9,
                "GB/s", ok, 0.0 if ok else float("inf")))

    # checkpoint save/load round-trip (train/checkpoint.py msgpack+JSON path)
    import jax

    from dcnn_tpu.models.zoo import create_resnet9_cifar10, create_mnist_trainer
    from dcnn_tpu.optim import Adam
    from dcnn_tpu.train.checkpoint import load_checkpoint, save_checkpoint
    from dcnn_tpu.train.trainer import create_train_state

    model = create_mnist_trainer() if tiny_mode() else create_resnet9_cifar10()
    opt = Adam(1e-3)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    param_bytes = sum(x.size * x.dtype.itemsize
                      for x in jax.tree_util.tree_leaves(ts.params))
    tmp = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        path = os.path.join(tmp, "ckpt")
        dt_s, _ = _time_host(lambda: save_checkpoint(
            path, model, ts.params, ts.state, ts.opt_state, opt), reps=3)
        dt_l, loaded = _time_host(lambda: load_checkpoint(path), reps=3)
        _, lp, _, lopt, _, _ = loaded
        ok = all(np.array_equal(np.asarray(a), np.asarray(b))
                 for a, b in zip(jax.tree_util.tree_leaves(ts.params),
                                 jax.tree_util.tree_leaves(lp)))
        ok = ok and lopt is not None
        results.append(Result("checkpoint_save", dt_s,
                              param_bytes / dt_s / 1e9, "GB/s(params)", ok,
                              0.0 if ok else float("inf"),
                              extra={"param_mb": round(param_bytes / 2**20, 1)}))
        results.append(Result("checkpoint_load", dt_l,
                              param_bytes / dt_l / 1e9, "GB/s(params)", ok,
                              0.0 if ok else float("inf")))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return report("serialization", results)


if __name__ == "__main__":
    doc = run()
    print_table(doc)
    sys.exit(0 if doc["all_correct"] else 1)
