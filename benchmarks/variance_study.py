"""Variance budget for the headline bench (VERDICT r4 #3).

Runs ``bench.py`` as the driver does — a fresh process per run, default
headline knobs — N times, collects the headline value plus the per-phase
walls bench.py now reports (compile / warmup / per-rep steady-state), and
decomposes the spread:

- **within-run**: spread of the BENCH_REPS rep timings inside one process
  (dispatch jitter on the tunnel, clock wander during the run);
- **between-run**: spread of the per-run best values across process
  instances (compile-cache state, tunnel session, chip clock/thermal state).

The feed sections are disabled per run (BENCH_PIPELINE=0) — they execute
AFTER the headline measurement and cannot influence it; skipping them keeps
10 runs tractable on the tunnelled host. Everything upstream of the headline
section is exactly the driver path.

Writes ``benchmarks/results_variance.json`` and prints a summary.

Usage: python benchmarks/variance_study.py [N]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(ROOT, "benchmarks", "results_variance.json")


def one_run(i: int) -> dict:
    env = dict(os.environ)
    # disable every feed section (they run after the headline measurement
    # and cannot influence it): resident + host-feed + streaming
    env["BENCH_PIPELINE"] = "0"
    env["BENCH_RESIDENT"] = "0"
    env["BENCH_STREAMING"] = "0"
    t0 = time.time()
    proc = subprocess.run([sys.executable, os.path.join(ROOT, "bench.py")],
                          capture_output=True, text=True, timeout=1800,
                          cwd=ROOT, env=env)
    wall = time.time() - t0
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    try:
        rec = json.loads(line)
    except json.JSONDecodeError:
        raise SystemExit(f"run {i}: no JSON line; stderr:\n{proc.stderr[-2000:]}")
    rec["_run_wall_s"] = round(wall, 1)
    print(f"run {i}: {rec['value']} img/s  compile {rec['phases']['compile_s']}s "
          f"reps {rec['phases']['rep_s']}  ({wall:.0f}s total)", flush=True)
    return rec


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    runs = [one_run(i) for i in range(n)]
    values = np.array([r["value"] for r in runs])
    batch = runs[0]["batch"]
    # rep-level throughput samples: batch*steps/rep_s per rep per run
    rep_ips = [[batch * r["phases"]["steps_per_rep"] / s
                for s in r["phases"]["rep_s"]] for r in runs]
    within = np.array([np.std(r) / np.mean(r) for r in rep_ips])
    run_means = np.array([np.mean(r) for r in rep_ips])
    run_bests = np.array([np.max(r) for r in rep_ips])

    doc = {
        "section": "variance_budget",
        "n_runs": n,
        "headline_values": values.tolist(),
        "value_min": float(values.min()),
        "value_median": float(np.median(values)),
        "value_max": float(values.max()),
        "value_spread_pct": round(
            100.0 * (values.max() - values.min()) / np.median(values), 2),
        "value_cv_pct": round(100.0 * values.std() / values.mean(), 2),
        # decomposition
        "within_run_cv_pct_mean": round(100.0 * within.mean(), 2),
        "between_run_cv_pct_of_best": round(
            100.0 * run_bests.std() / run_bests.mean(), 2),
        "between_run_cv_pct_of_mean": round(
            100.0 * run_means.std() / run_means.mean(), 2),
        "compile_s": [r["phases"]["compile_s"] for r in runs],
        "warmup_s": [r["phases"]["warmup_s"] for r in runs],
        "rep_s": [r["phases"]["rep_s"] for r in runs],
        "run_wall_s": [r["_run_wall_s"] for r in runs],
        "conditions": {"batch": batch, "format": runs[0]["format"],
                       "precision": runs[0]["precision"],
                       "steps_per_dispatch": runs[0]["steps_per_dispatch"],
                       "device": runs[0]["device_kind"],
                       "feed_sections": "disabled (BENCH_PIPELINE/"
                                        "RESIDENT/STREAMING=0)"},
    }
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({k: doc[k] for k in (
        "value_min", "value_median", "value_max", "value_spread_pct",
        "value_cv_pct", "within_run_cv_pct_mean",
        "between_run_cv_pct_of_best")}, indent=1))
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
