"""Bench-history regression gate CLI over ``BENCH_r*.json`` captures.

Thin driver around :mod:`dcnn_tpu.obs.regress` (semantics documented
there: newest capture vs the best of a trailing window, per metric, with
per-metric noise tolerances and a cache-warmth guard on ``compile_s``).

Usage::

    python benchmarks/compare.py                 # repo-root BENCH_r*.json
    python benchmarks/compare.py A.json B.json   # explicit history, oldest
                                                 # first; last file is gated
    python benchmarks/compare.py --window 3 --tolerance 0.15
    python benchmarks/compare.py --json          # machine-readable report
    python benchmarks/compare.py --self-test     # fixture run (tier-1)

Exit code: 0 = no regressions, 1 = regression(s) flagged, 2 = usage /
unreadable history. A CI job gates on exactly that.

``--self-test`` regression-tests the gate itself: it writes fixture BENCH
files mimicking the real r01–r05 trajectory into a temp dir, appends a
capture with a planted 25% img/s regression, and asserts the gate flags
the planted file and passes the clean history. Tier-1 runs this via
``tests/test_regress.py``, so a gate that stops gating fails CI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from dcnn_tpu.obs import regress  # noqa: E402


# Fixture trajectory for --self-test: the shape of the real r01–r05 story
# (monotone img/s growth, metrics appearing over time, one noisy h2d
# series) without depending on the repo files being present.
_FIXTURE_HISTORY = [
    {"metric": "m", "value": 6738.9},
    {"metric": "m", "value": 22353.8, "mfu": 0.3704, "h2d_gbps": 0.033},
    {"metric": "m", "value": 24342.0, "mfu": 0.4033, "h2d_gbps": 0.010},
    {"metric": "m", "value": 25254.9, "mfu": 0.4184, "h2d_gbps": 0.032},
    {"metric": "m", "value": 26389.8, "mfu": 0.4372, "h2d_gbps": 0.011,
     "infer_int8_img_per_sec": 229188.1,
     "phases": {"compile_s": 149.895, "compile_cache_hit": None}},
]
# planted: img/s down 25% vs the window best — the gate MUST flag this
_FIXTURE_REGRESSED = {
    "metric": "m", "value": 19792.0, "mfu": 0.4361, "h2d_gbps": 0.028,
    "infer_int8_img_per_sec": 231002.5,
    "phases": {"compile_s": 151.2, "compile_cache_hit": None}}
# planted-clean: everything within tolerance — the gate MUST pass this
_FIXTURE_CLEAN = {
    "metric": "m", "value": 26011.4, "mfu": 0.4330, "h2d_gbps": 0.029,
    "infer_int8_img_per_sec": 228104.0,
    "phases": {"compile_s": 148.0, "compile_cache_hit": None}}


def self_test() -> int:
    """Fixture run: write BENCH files, plant a regression, assert the gate
    catches exactly it. Prints PASS/FAIL lines; returns an exit code."""
    failures = []

    def check(name, cond):
        print(f"  {'PASS' if cond else 'FAIL'}: {name}")
        if not cond:
            failures.append(name)

    with tempfile.TemporaryDirectory() as d:
        for i, cap in enumerate(_FIXTURE_HISTORY, start=1):
            with open(os.path.join(d, f"BENCH_r{i:02d}.json"), "w") as f:
                json.dump({"n": i, "parsed": cap}, f)
        files = regress.find_bench_files(d)
        check("fixture discovery finds 5 captures in order",
              len(files) == 5 and files == sorted(files))

        clean = regress.compare_files(files)
        check("clean fixture trajectory passes", clean["ok"])

        # append the planted-regression capture as r06 and re-gate
        with open(os.path.join(d, "BENCH_r06.json"), "w") as f:
            json.dump({"n": 6, "parsed": _FIXTURE_REGRESSED}, f)
        flagged = regress.compare_files(regress.find_bench_files(d))
        check("planted 25% img/s regression is flagged",
              not flagged["ok"] and "img_per_sec" in flagged["regressions"])
        check("only the planted metric is flagged",
              flagged["regressions"] == ["img_per_sec"])

        # replace r06 with an in-tolerance capture: must pass again
        with open(os.path.join(d, "BENCH_r06.json"), "w") as f:
            json.dump({"n": 6, "parsed": _FIXTURE_CLEAN}, f)
        ok_again = regress.compare_files(regress.find_bench_files(d))
        check("in-tolerance follow-up capture passes", ok_again["ok"])

        # lower-is-better direction: compile_s blowing up must flag (same
        # cache-warmth guard value as the prior capture)
        blown = dict(_FIXTURE_CLEAN)
        blown["phases"] = {"compile_s": 400.0, "compile_cache_hit": None}
        with open(os.path.join(d, "BENCH_r06.json"), "w") as f:
            json.dump({"n": 6, "parsed": blown}, f)
        comp = regress.compare_files(regress.find_bench_files(d))
        check("compile_s blow-up (same cache state) is flagged",
              "compile_s" in comp["regressions"])

        # ...but a cache-warmth change makes compile_s incomparable
        warm = dict(blown)
        warm["phases"] = {"compile_s": 400.0, "compile_cache_hit": True}
        with open(os.path.join(d, "BENCH_r06.json"), "w") as f:
            json.dump({"n": 6, "parsed": warm}, f)
        guarded = regress.compare_files(regress.find_bench_files(d))
        row = next(r for r in guarded["metrics"]
                   if r["metric"] == "compile_s")
        check("compile_s skipped across a cache-warmth change",
              row["verdict"].startswith("skipped"))

    print("self-test:", "PASS" if not failures else
          f"FAIL ({len(failures)}: {failures})")
    return 0 if not failures else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="gate the newest BENCH capture against the trailing "
                    "window of prior captures")
    ap.add_argument("files", nargs="*",
                    help="capture files oldest->newest (default: "
                         "BENCH_r*.json in the repo root)")
    ap.add_argument("--window", type=int, default=regress.DEFAULT_WINDOW,
                    help="trailing captures compared per metric "
                         "(default %(default)s)")
    ap.add_argument("--tolerance", type=float,
                    default=regress.DEFAULT_TOLERANCE,
                    help="default relative tolerance; per-metric overrides "
                         "in obs/regress.py still apply "
                         "(default %(default)s)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    ap.add_argument("--self-test", action="store_true",
                    help="run the fixture-based gate self-test and exit")
    args = ap.parse_args(argv)

    if args.self_test:
        return self_test()

    files = args.files or regress.find_bench_files(_ROOT)
    if len(files) < 2:
        print(f"need >= 2 captures to compare, found {len(files)} "
              f"({files or 'no BENCH_r*.json in ' + _ROOT})",
              file=sys.stderr)
        return 2
    try:
        report = regress.compare_files(files, window=args.window,
                                       tolerance=args.tolerance)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(f"gating {os.path.basename(report['files'][-1])} against "
              f"{len(report['files']) - 1} prior capture(s), "
              f"window {report['window']}:")
        print(regress.format_report(report))
        if report["unparseable_files"]:
            print(f"  (skipped unparseable: "
                  f"{report['unparseable_files']})")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
