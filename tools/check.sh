#!/usr/bin/env bash
# The one pre-merge gate: lint -> static analysis -> coverage lints ->
# bench-gate self-test.
#
#   tools/check.sh                 # full run, fail on any gate
#   tools/check.sh --changed-only  # analysis scoped to git-changed files
#
# --changed-only keeps the loop fast as the package grows: stage 2
# still analyzes the whole package (the call graph, DL01's lock graph
# and the PR01/PR02 protocol map are whole-project facts — a file-scoped
# parse would fabricate '<no-handler>' findings for senders whose
# handler lives elsewhere) but REPORTS only findings in the dcnn_tpu/*.py
# files changed vs HEAD (staged, unstaged, and the last commit) via
# --only, and ruff runs on just that set. Stage 3's cross-directory
# lints are skipped. The full run remains the tier-1 contract
# (tests/test_analysis.py::test_live_package_zero_unsuppressed).
#
# Stages:
#   1. ruff (error tier + bugbear subset B006/B008/B023/B025,
#      [tool.ruff.lint] in pyproject.toml). Skipped with a notice when
#      ruff is not installed — the container image does not ship it; the
#      AST-level F-class issues are then still partially covered by
#      stage 2's parse pass.
#   2. python -m dcnn_tpu.analysis — trace-safety (TS01-TS06 incl. the
#      retrace/recompile check), concurrency (CC01-CC03), deadlock
#      (DL01 lock-order cycles, DL02 blocking-under-lock), frame-protocol
#      conformance over the four framed-TCP surfaces (PR01 handler
#      exhaustiveness, PR02 generation/nonce fencing), and atomicity
#      (AT01) against the committed baseline (docs/static_analysis.md).
#      Zero unsuppressed findings required. The monitoring-plane modules
#      (obs/tsdb.py sampler thread -> CC02 lifecycle + AT01 persistence,
#      obs/rules.py edge state + obs/fleet.py poll thread -> CC01
#      guarded_by) are covered with zero baseline entries, as are the
#      continuous-batching decode modules (serve/kvcache.py free-list +
#      tables and serve/decode.py scheduler state -> CC01 guarded_by;
#      the bucketed decode step -> TS06 retrace-clean: one jit, per-
#      bucket AOT sessions).
#   3. coverage lints (full runs only — they span tests/ and docs/):
#      --fault-coverage (every FaultPlan trip point armed by a test),
#      --metric-drift (obs.registry emissions <-> docs/observability.md,
#      both directions), and --span-coverage (every recorded tracer span
#      maps to a goodput bucket in obs/goodput.SPAN_BUCKETS).
#   4. benchmarks/compare.py --self-test — the bench regression gate's
#      own fixture run (planted 25% drop must flag; clean history must
#      pass).
#
# Tier-1 pytest is intentionally NOT chained here (it has its own runner
# and budget); this script is the fast pre-merge loop.
set -uo pipefail
cd "$(dirname "$0")/.."

changed_only=0
for arg in "$@"; do
  case "$arg" in
    --changed-only) changed_only=1 ;;
    *) echo "unknown argument: $arg" >&2; exit 2 ;;
  esac
done

fail=0

# the report scope: everything, or just the changed dcnn_tpu python files
analysis_args=(dcnn_tpu/)
run_analysis=1
ruff_paths=(.)
if [[ "$changed_only" == 1 ]]; then
  mapfile -t changed < <(
    { git diff --name-only HEAD 2>/dev/null;
      git diff --name-only --cached 2>/dev/null;
      git diff --name-only HEAD~1..HEAD 2>/dev/null; } \
    | sort -u | grep -E '^dcnn_tpu/.*\.py$' || true)
  # drop deleted files — the analyzers read from disk
  existing=()
  for f in "${changed[@]:-}"; do
    [[ -n "$f" && -f "$f" ]] && existing+=("$f")
  done
  if [[ ${#existing[@]} -eq 0 ]]; then
    echo "== changed-only: no changed dcnn_tpu/*.py files — analysis skipped =="
    run_analysis=0
    ruff_paths=()
  else
    echo "== changed-only: reporting ${#existing[@]} file(s) =="
    only=$(IFS=,; echo "${existing[*]}")
    analysis_args=(dcnn_tpu/ --only "$only")
    ruff_paths=("${existing[@]}")
  fi
fi

echo "== [1/4] ruff (E/F error tier + bugbear subset) =="
if command -v ruff >/dev/null 2>&1; then
  if [[ ${#ruff_paths[@]} -gt 0 ]] && ! ruff check "${ruff_paths[@]}"; then
    fail=1
  fi
else
  echo "ruff not installed — skipped (pip install ruff to enable)"
fi

echo "== [2/4] dcnn_tpu.analysis =="
if [[ "$run_analysis" == 1 ]]; then
  if ! python -m dcnn_tpu.analysis "${analysis_args[@]}"; then
    fail=1
  fi
fi

if [[ "$changed_only" == 1 ]]; then
  echo "== [3/4] coverage lints — skipped under --changed-only =="
else
  echo "== [3/4] fault-coverage + metric-drift + span-coverage lints =="
  if ! python -m dcnn_tpu.analysis dcnn_tpu --fault-coverage --metric-drift --span-coverage; then
    fail=1
  fi
fi

echo "== [4/4] bench regression gate self-test =="
if ! python benchmarks/compare.py --self-test; then
  fail=1
fi

if [[ "$fail" != 0 ]]; then
  echo "CHECK FAILED" >&2
  exit 1
fi
echo "all checks passed"
