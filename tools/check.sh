#!/usr/bin/env bash
# The one pre-merge gate: lint -> static analysis -> bench-gate self-test.
#
#   tools/check.sh            # run everything available, fail on any gate
#
# Stages:
#   1. ruff (error-tier E/F rules, [tool.ruff] in pyproject.toml). Skipped
#      with a notice when ruff is not installed — the container image does
#      not ship it; the AST-level F-class issues are then still partially
#      covered by stage 2's parse pass.
#   2. python -m dcnn_tpu.analysis dcnn_tpu/ — the trace-safety /
#      concurrency / atomicity suite against the committed baseline
#      (docs/static_analysis.md). Zero unsuppressed findings required;
#      this covers dcnn_tpu/aot/ (CC03 resource-lifecycle applies to its
#      cross-process file locks), the autoscaler pair
#      serve/autoscale.py + parallel/autoscale.py (CC01 guarded_by
#      discipline on shared scaler/broker/lease state, CC02 on the
#      control-loop poll thread and leased-segment runners), and the
#      distributed-tracing layer obs/flight.py + obs/trace.py (AT01
#      atomic-commit on bundle staging and the merged-trace write, CC01
#      on the recorder's cooldown/seq state and the healthz edge lock)
#      — all with zero baseline entries. The tracer's context plumbing
#      keeps the disabled-path <100 ns no-op bound, asserted in
#      tests/test_obs.py (propagation must cost nothing when off).
#      The self-healing pipeline pair parallel/distributed_pipeline.py +
#      parallel/worker.py is covered the same way: CC01 guarded_by
#      discipline on the coordinator's liveness tables and the worker's
#      beat-visible state, CC02 on both beat threads (daemon +
#      stop-event + joined in shutdown()/serve()'s finally) — zero new
#      baseline entries.
#   3. benchmarks/compare.py --self-test — the bench regression gate's own
#      fixture run (planted 25% drop must flag; clean history must pass).
#
# Tier-1 pytest is intentionally NOT chained here (it has its own runner
# and budget); this script is the fast pre-merge loop.
set -uo pipefail
cd "$(dirname "$0")/.."

fail=0

echo "== [1/3] ruff (E/F error tier) =="
if command -v ruff >/dev/null 2>&1; then
  if ! ruff check .; then
    fail=1
  fi
else
  echo "ruff not installed — skipped (pip install ruff to enable)"
fi

echo "== [2/3] dcnn_tpu.analysis =="
if ! python -m dcnn_tpu.analysis dcnn_tpu/; then
  fail=1
fi

echo "== [3/3] bench regression gate self-test =="
if ! python benchmarks/compare.py --self-test; then
  fail=1
fi

if [[ "$fail" != 0 ]]; then
  echo "CHECK FAILED" >&2
  exit 1
fi
echo "all checks passed"
